"""Serving-stack bench driver + CI smoke.

    python -m tools.serve_bench --selftest
        <30s, JAX_PLATFORMS=cpu: drives a tiny decoder through
        prefill -> continuous decode -> retire in-process, asserts the
        scheduler/page-pool invariants and the serving/* counters, then
        runs the bench path end-to-end with the ragged paged-attention
        kernel armed (interpret mode) and checks kernel provenance plus
        the run-ledger/perf-gate mechanics. The smoke-gate entry
        (ROADMAP).

    python -m tools.serve_bench [--requests N] [--slots S] [--seed K]
                                [--kernel {auto,gather,paged}]
        Small synthetic mixed-length serve bench on the current backend:
        ragged continuous batching vs the padded static-batch baseline,
        printed as JSON (p50/p99 latency, sustained QPS, tokens/s).
        ``--kernel`` selects the decode-attention A/B: the gather legs
        always run (the baseline the run ledger gates); ``paged`` adds a
        ``continuous_paged_kernel`` leg with the ragged paged-attention
        Pallas kernel armed (interpret mode off-TPU — a parity/mechanism
        leg there, a perf leg on hardware) and reports the kernel:gather
        QPS + tokens/s ratios; ``auto`` (default) adds that leg only
        where the kernel compiles (TPU). A ``continuous_paged_speculative``
        leg always rides along: the same stream through the draft-verify
        fast path (``speculation="auto"``), reporting acceptance rate and
        tokens-per-dispatch next to its tokens/s.

``bench.py --serve`` imports :func:`serve_bench` from here, so the bench
leg and the smoke share one driver.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402

from paddle_tpu.monitor.metrics import sorted_percentile  # noqa: E402

# the engine currently being driven by drive() — what the SIGTERM handler
# drains instead of letting the process die mid-decode
_live_engine = [None]

# counters drive() snapshots around each leg so the digest can report
# per-leg speculative accounting (the registry is process-global)
_LEG_COUNTERS = ("serving/spec_proposed_tokens",
                 "serving/spec_accepted_tokens",
                 "serving/decode_dispatches")

# digest fields that ride into the run ledger although they are strings —
# the per-leg provenance (which kernel / drafter / table layer ran)
_PROVENANCE_KEYS = ("decode_kernel", "decode_kernel_source",
                    "spec_drafter", "speculation_source")


def _counter_values():
    from paddle_tpu.monitor import metrics as mx

    snap = mx.snapshot()
    return {n: float(snap.get(n, {}).get("value", 0.0))
            for n in _LEG_COUNTERS}


def _ledger_fields(digest):
    """The numeric fields of a leg digest plus its provenance strings —
    what one run-ledger config record carries for that leg."""
    return {k: v for k, v in digest.items()
            if isinstance(v, (int, float)) or k in _PROVENANCE_KEYS}


def _install_sigterm_drain() -> None:
    """Bench-mode graceful shutdown: SIGTERM requests a drain on the live
    engine (finish in-flight, shed queued, close) instead of killing the
    process mid-decode; drive() prints the drain summary and exits 0."""

    def handler(signum, frame):
        eng = _live_engine[0]
        if eng is None:
            raise SystemExit(143)
        eng.request_drain()  # run() performs the drain at the next cycle

    signal.signal(signal.SIGTERM, handler)


def make_stream(n_requests, vocab, max_prompt, max_new_hi, seed=0,
                min_prompt=4, min_new=4):
    """Synthetic mixed-length request stream: (prompt, max_new) pairs with
    uniformly ragged prompt lengths and generation budgets — the shape
    continuous batching wins on and padded static batching pays for."""
    rng = np.random.RandomState(seed)
    stream = []
    for _ in range(n_requests):
        p_len = int(rng.randint(min_prompt, max_prompt + 1))
        n_new = int(rng.randint(min_new, max_new_hi + 1))
        stream.append((list(rng.randint(0, vocab, p_len)), n_new))
    return stream


def drive(model, stream, scfg, warmup=True, keep_open=False):
    """Submit ``stream`` to a fresh engine and drain it; returns the
    latency/throughput digest. Compiles are excluded from the timed region
    via :meth:`ServingEngine.warmup` (steady-state serving numbers).
    ``keep_open=False`` closes the engine (releasing its telemetry
    reference) before returning."""
    from paddle_tpu import serving

    eng = serving.ServingEngine(model, scfg)
    _live_engine[0] = eng
    if warmup:
        eng.warmup()
    c0 = _counter_values()
    t0 = time.perf_counter()
    reqs = []
    try:
        for p, m in stream:
            reqs.append(eng.submit(p, m))
    except serving.DrainingError:
        pass  # SIGTERM between legs: serve what was accepted, then drain
    done = eng.run()
    wall = time.perf_counter() - t0
    _live_engine[0] = None
    if eng._draining and eng.last_drain is None:
        eng.drain()  # drain requested while idle: produce summary + close
    if eng.last_drain is not None:
        # a SIGTERM drained us mid-bench: report what was served and leave
        # cleanly (the engine already closed itself)
        print(json.dumps({"drained": eng.last_drain,
                          "served": len([r for r in reqs
                                         if r.state == "finished"])}))
        raise SystemExit(0)
    if not keep_open:
        eng.close()
    assert len(done) == len(reqs), "stream did not drain: %d/%d" % (
        len(done), len(reqs))
    c1 = _counter_values()
    lat_ms = sorted(1e3 * r.latency_s for r in reqs)
    ttft_ms = sorted(1e3 * r.ttft_s for r in reqs)
    tokens = sum(len(r.tokens_out) for r in reqs)
    digest = {
        "mode": ("continuous" if scfg.continuous else "static_padded")
                + "_" + eng.cache_ops.layout,
        "requests": len(reqs),
        "wall_s": round(wall, 3),
        "qps": round(len(reqs) / wall, 3),
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2),
        "latency_p50_ms": round(sorted_percentile(lat_ms, 50), 2),
        "latency_p99_ms": round(sorted_percentile(lat_ms, 99), 2),
        "ttft_p50_ms": round(sorted_percentile(ttft_ms, 50), 2),
        "ttft_p99_ms": round(sorted_percentile(ttft_ms, 99), 2),
        "cache_bytes": eng.stats()["cache_bytes"],
        # which decode-attention inner loop THIS leg ran, with the tune-
        # table layer that supplied its block config (tuned/shipped/
        # default) — the per-kernel provenance the summary tail carries
        "decode_kernel": eng.stats()["decode_kernel"],
        "decode_kernel_source": eng.stats()["decode_kernel_source"],
    }
    spec_k, spec_kind, spec_src = eng.speculation_info()
    if spec_k > 0:
        # the speculative leg's own accounting, from this leg's counter
        # deltas: how many draft tokens the target accepted, and how many
        # tokens each model dispatch retired on average (> 1.0 = the
        # draft-verify window is paying for itself)
        proposed = c1[_LEG_COUNTERS[0]] - c0[_LEG_COUNTERS[0]]
        accepted = c1[_LEG_COUNTERS[1]] - c0[_LEG_COUNTERS[1]]
        dispatches = c1[_LEG_COUNTERS[2]] - c0[_LEG_COUNTERS[2]]
        digest.update({
            "speculation": spec_k,
            "spec_drafter": spec_kind,
            "speculation_source": spec_src,
            "spec_proposed": int(proposed),
            "spec_accepted": int(accepted),
            "acceptance_rate": round(accepted / max(1.0, proposed), 4),
            "tokens_per_dispatch": round(tokens / max(1.0, dispatches), 3),
        })
    return digest, eng


def resolve_decode_fuse(decode_fuse, slots):
    """(value, source) for the bench's ``decode_fuse``: an explicit int is
    honored verbatim; ``None`` resolves through the SAME
    ``tune.resolve_decode_fuse`` helper ``ServingConfig(decode_fuse=
    "auto")`` uses, so the bench reports exactly what the engine runs."""
    if decode_fuse is not None:
        return int(decode_fuse), "explicit"
    from paddle_tpu import tune

    return tune.resolve_decode_fuse(slots)


def serve_bench(n_requests=64, slots=8, vocab=512, n_layer=4, d_model=128,
                n_head=4, max_seq=256, page_size=16, max_prompt=128,
                max_new_hi=64, decode_fuse=None, seed=0, kernel="auto"):
    """Ragged continuous batching vs the padded static-batch baseline on
    the SAME synthetic mixed-length stream. Returns the comparison dict
    ``bench.py --serve`` embeds (and summarizes in its truncation-proof
    tail). ``decode_fuse=None`` = consult the autotuned table (the config
    block reports the value AND which layer supplied it). ``kernel``
    selects the decode-attention A/B leg (see the module docstring): the
    gather legs are ALWAYS pinned to the gather path so the ledger
    baseline stays comparable across flag environments."""
    from paddle_tpu import serving
    from paddle_tpu.flags import flags, set_flag
    from paddle_tpu.models import decoder_lm

    if kernel not in ("auto", "gather", "paged"):
        raise ValueError("kernel must be auto|gather|paged, got %r" % kernel)
    decode_fuse, fuse_src = resolve_decode_fuse(decode_fuse, slots)
    cfg = decoder_lm.DecoderConfig(vocab_size=vocab, n_layer=n_layer,
                                   d_model=d_model, n_head=n_head,
                                   max_seq=max_seq)
    model = decoder_lm.DecoderLM(cfg, seed=seed)
    stream = make_stream(n_requests, vocab, max_prompt, max_new_hi, seed=seed)

    prev_kernel = flags.paged_attention_kernel
    set_flag("paged_attention_kernel", "off")
    try:
        ragged, eng = drive(model, stream, serving.ServingConfig(
            slots=slots, page_size=page_size, max_seq=max_seq,
            decode_fuse=decode_fuse, paged=True, continuous=True))
        padded, _ = drive(model, stream, serving.ServingConfig(
            slots=slots, page_size=page_size, max_seq=max_seq,
            decode_fuse=decode_fuse, paged=False, continuous=False))
        out = {
            "config": {"requests": n_requests, "slots": slots, "vocab": vocab,
                       "n_layer": n_layer, "d_model": d_model,
                       "n_head": n_head,
                       "max_seq": max_seq, "page_size": page_size,
                       "max_prompt": max_prompt, "max_new_hi": max_new_hi,
                       "decode_fuse": decode_fuse,
                       "decode_fuse_source": fuse_src, "seed": seed,
                       "kernel": kernel,
                       "backend": _backend()},
            "continuous_paged": ragged,
            "static_padded": padded,
            "qps_ratio_vs_padded": round(ragged["qps"] / padded["qps"], 3),
        }
        # the A/B leg: SAME stream, SAME geometry, decode attention through
        # the ragged paged-attention Pallas kernel. "auto" only where it
        # compiles — the interpreter leg is opt-in (--kernel paged) because
        # it measures the interpreter, not the kernel.
        want_kernel = kernel == "paged" or (
            kernel == "auto" and _backend() == "tpu")
        if want_kernel:
            try:
                set_flag("paged_attention_kernel",
                         "on" if _backend() == "tpu" else "interpret")
                kleg, _ = drive(model, stream, serving.ServingConfig(
                    slots=slots, page_size=page_size, max_seq=max_seq,
                    decode_fuse=decode_fuse, paged=True, continuous=True))
                kleg["mode"] = "continuous_paged_kernel"
                out["continuous_paged_kernel"] = kleg
                out["kernel_vs_gather"] = {
                    "qps_ratio": round(kleg["qps"] / ragged["qps"], 3),
                    "tokens_per_sec_ratio": round(
                        kleg["tokens_per_sec"] / ragged["tokens_per_sec"],
                        3),
                }
            except Exception as e:  # A/B leg must never sink the baseline
                out["continuous_paged_kernel"] = {"error": repr(e)[:200]}
            finally:
                set_flag("paged_attention_kernel", "off")
        try:
            # the paged capacity story: HALF the KV pages of the worst case
            # — ragged lengths mean real occupancy rarely needs it — served
            # by admission backpressure, not crashes. Reported as its own
            # leg so the headline ratio stays an equal-memory comparison.
            half_pages = max(slots, (slots * (max_seq // page_size)) // 2)
            over, _ = drive(model, stream, serving.ServingConfig(
                slots=slots, page_size=page_size, max_seq=max_seq,
                num_pages=half_pages, decode_fuse=decode_fuse,
                paged=True, continuous=True))
            over["num_pages"] = half_pages
            out["continuous_paged_half_pool"] = over
            out["half_pool_cache_bytes_saved"] = (
                padded["cache_bytes"] - over["cache_bytes"])
        except Exception as e:  # the demo leg must never sink the headline
            out["continuous_paged_half_pool"] = {"error": repr(e)[:200]}
        try:
            # the quantized-capacity story: calibrate this model's KV
            # scales from the fp leg's OWN pages (the amax those pages
            # really saw), publish to a throwaway calibration table, and
            # serve the SAME stream through int8 pages with TWICE the
            # page budget — which still costs fewer cache bytes than the
            # fp pool, while the greedy stream generates the same token
            # volume (logits tolerance is pinned down in selftest()).
            import tempfile

            from paddle_tpu.monitor import numerics as _num

            mcfg = model.cfg
            k_amax = float(np.abs(np.asarray(eng._cache["k"])).max())
            v_amax = float(np.abs(np.asarray(eng._cache["v"])).max())
            fp_key = _num.kv_fingerprint(mcfg.n_layer, mcfg.n_head,
                                         mcfg.d_head, mcfg.dtype)
            tbl = os.path.join(tempfile.mkdtemp(prefix="serve_calib_"),
                               "calib.json")
            _num.record_kv_calibration(fp_key, k_amax, v_amax, path=tbl)
            prev_tbl = os.environ.get("PADDLE_TPU_NUMERICS_TABLE")
            os.environ["PADDLE_TPU_NUMERICS_TABLE"] = tbl
            try:
                full_pages = slots * (max_seq // page_size)
                i8, _ = drive(model, stream, serving.ServingConfig(
                    slots=slots, page_size=page_size, max_seq=max_seq,
                    num_pages=2 * full_pages, decode_fuse=decode_fuse,
                    paged=True, continuous=True, kv_dtype="int8"))
                i8["num_pages"] = 2 * full_pages
                out["continuous_paged_int8_2x"] = i8
                out["int8_2x_vs_fp"] = {
                    "token_parity": i8["tokens"] == ragged["tokens"],
                    "pages_ratio": 2.0,
                    "cache_bytes_ratio": round(
                        i8["cache_bytes"] / ragged["cache_bytes"], 3),
                }
            finally:
                if prev_tbl is None:
                    os.environ.pop("PADDLE_TPU_NUMERICS_TABLE", None)
                else:
                    os.environ["PADDLE_TPU_NUMERICS_TABLE"] = prev_tbl
        except Exception as e:  # calibration leg must never sink the headline
            out["continuous_paged_int8_2x"] = {"error": repr(e)[:200]}
        try:
            # the speculative leg: the SAME greedy stream through the
            # draft-verify fast path — a zero-weight n-gram drafter
            # proposes k tokens per tick and the target verifies the
            # whole window in ONE fused dispatch riding the same paged
            # layout. k resolves through the tune table ("auto"), and the
            # acceptance theorem makes the greedy stream bit-identical to
            # plain decode, so token_parity is an invariant, not luck.
            sp, _ = drive(model, stream, serving.ServingConfig(
                slots=slots, page_size=page_size, max_seq=max_seq,
                decode_fuse=decode_fuse, paged=True, continuous=True,
                speculation="auto"))
            sp["mode"] = "continuous_paged_speculative"
            out["continuous_paged_speculative"] = sp
            out["speculative_vs_plain"] = {
                "token_parity": sp["tokens"] == ragged["tokens"],
                "tokens_per_sec_ratio": round(
                    sp["tokens_per_sec"] / ragged["tokens_per_sec"], 3),
                "acceptance_rate": sp.get("acceptance_rate", 0.0),
                "tokens_per_dispatch": sp.get("tokens_per_dispatch", 0.0),
            }
        except Exception as e:  # the spec leg must never sink the headline
            out["continuous_paged_speculative"] = {"error": repr(e)[:200]}
    finally:
        set_flag("paged_attention_kernel", prev_kernel)
    # observability artifact pointers for the summary tail: with
    # PADDLE_TPU_TRACE_FILE set the per-request serving spans land in that
    # Chrome trace at exit (open in Perfetto — one track per slot), and
    # with PADDLE_TPU_TELEMETRY_DIR the run leaves a JSONL metrics series
    trace_file = os.environ.get("PADDLE_TPU_TRACE_FILE", "").strip()
    if trace_file:
        out["trace_file"] = trace_file
    telemetry_dir = os.environ.get("PADDLE_TPU_TELEMETRY_DIR", "").strip()
    if telemetry_dir:
        out["telemetry_dir"] = telemetry_dir
    return out


def _backend():
    import jax

    return jax.default_backend()


def selftest() -> int:
    """Tiny decoder through prefill -> decode -> retire in-process, CPU,
    <30s: the CI gate for the serving stack. Runs with the host
    tracer on, so it also asserts the per-request span sets (every
    terminal request complete + well-nested, no queued-without-terminal
    orphans) across the FINISHED, TIMEOUT and FAILED paths."""
    import tempfile

    from paddle_tpu import serving
    from paddle_tpu.models import decoder_lm
    from paddle_tpu.monitor import metrics as mx, tracer
    from paddle_tpu.serving import trace as strace

    t0 = time.perf_counter()
    tracer.start_tracing()
    all_reqs = []  # every request the drill creates, for span validation
    cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=2, d_model=32,
                                   n_head=2, max_seq=64)
    model = decoder_lm.DecoderLM(cfg, seed=0)
    eng = serving.ServingEngine(model, serving.ServingConfig(
        slots=4, page_size=8, max_seq=64))
    rng = np.random.RandomState(0)
    reqs = []
    for _ in range(6):
        p = list(rng.randint(0, 64, int(rng.randint(3, 24))))
        reqs.append(eng.submit(p, int(rng.randint(2, 10))))
    all_reqs.extend(reqs)
    done = eng.run()
    assert len(done) == 6, "drain incomplete: %d/6" % len(done)
    for r in reqs:
        assert r.state == "finished" and r.slot is None and not r.pages
        assert len(r.tokens_out) == r.max_new_tokens, r
        assert r.latency_s is not None and r.ttft_s is not None
    assert eng.scheduler.idle() and eng.pool.num_used == 0
    # page-leak invariant: every retirement path must have returned its
    # pages — the pool's used count equals the pages held by running
    # requests (zero here), and the engine agrees it is healthy
    assert eng.page_accounting_ok(), "page accounting leak after drain"
    health = eng.health()
    assert health["status"] == "ok" and health["page_accounting_ok"], health
    # a deadline of 0 must be retired TIMEOUT without pinning slot or pages
    late = eng.submit([1, 2, 3], 4, deadline_s=0.0)
    all_reqs.append(late)
    eng.run(max_steps=50)
    assert late.state == "timeout" and not late.pages, late
    assert eng.pool.num_used == 0 and eng.page_accounting_ok()
    # the serving/* instruments must exist and be consistent
    snap = mx.snapshot()
    for name in ("serving/requests_submitted", "serving/requests_admitted",
                 "serving/requests_retired", "serving/tokens_generated",
                 "serving/decode_steps", "serving/prefills",
                 "serving/request_latency_ms", "serving/ttft_ms",
                 "serving/page_pool_pages_in_use",
                 "serving/faults", "serving/retries", "serving/timeouts",
                 "serving/requests_failed"):
        assert name in snap, "missing instrument %s" % name
    assert snap["serving/timeouts"]["value"] >= 1
    assert snap["serving/requests_retired"]["value"] >= 6
    assert snap["serving/requests_admitted"]["value"] >= 6
    assert snap["serving/tokens_generated"]["value"] >= sum(
        r.max_new_tokens for r in reqs)
    assert snap["serving/request_latency_ms"]["count"] >= 6
    # the tuned decode_fuse hookup: the bench reports which table layer
    # supplied the value (plain "default" in CI — no tuned table present,
    # but a tuned entry written by tools/autotune.py flows through here)
    fuse_val, fuse_src = resolve_decode_fuse(None, 4)
    assert fuse_val >= 1 and fuse_src in ("tuned", "shipped", "default"), (
        fuse_val, fuse_src)
    assert eng.stats()["decode_fuse_source"] == "explicit"
    # backpressure: the bounded queue rejects with the typed error (submit
    # never compiles, so this costs nothing)
    eng2 = serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=64, max_queue=2))
    eng2.submit([1, 2, 3], 4)
    eng2.submit([1, 2, 3], 4)
    try:
        eng2.submit([1, 2, 3], 4)
        raise AssertionError("bounded queue did not backpressure")
    except serving.BackpressureError:
        pass
    assert mx.snapshot()["serving/requests_rejected"]["value"] >= 1
    eng2.close()
    # FAILED path: a fatal injected decode failure evicts the in-flight
    # batch — those requests must ALSO leave complete span sets (FAILED
    # terminal), not orphans
    from paddle_tpu.reliability import FaultPlan

    failed_req = eng.submit(list(rng.randint(0, 64, 5)), 8)
    all_reqs.append(failed_req)
    with FaultPlan.parse("serving.decode@1=fatal"):
        eng.run(max_steps=20)
    assert failed_req.state == "failed", failed_req
    assert eng.page_accounting_ok() and eng.pool.num_used == 0
    eng.close()
    # graceful drain through the REAL signal path: SIGTERM flips the live
    # engine into drain mode — in-flight requests finish, queued ones are
    # shed with the typed terminal, new submissions reject typed, the
    # engine closes. (mid-decode teardown is exactly what this replaces)
    eng4 = serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=64))
    d_reqs = [eng4.submit(list(rng.randint(0, 64, 6)), 4) for _ in range(4)]
    eng4.step()  # admit 2 into slots; 2 stay queued
    _live_engine[0] = eng4
    prev = signal.signal(signal.SIGTERM, signal.SIG_DFL)
    try:
        _install_sigterm_drain()
        os.kill(os.getpid(), signal.SIGTERM)  # handled: requests the drain
    finally:
        signal.signal(signal.SIGTERM, prev)
        _live_engine[0] = None
    try:
        eng4.submit([1, 2, 3], 4)
        raise AssertionError("draining engine accepted a submission")
    except serving.DrainingError:
        pass
    eng4.run(max_steps=100)  # performs the drain at the cycle boundary
    summary = eng4.last_drain
    assert summary is not None, "SIGTERM did not trigger a drain"
    assert summary["finished"] == 2 and summary["rejected"] == 2, summary
    states = sorted(r.state for r in d_reqs)
    assert states == ["finished", "finished", "rejected", "rejected"], states
    # drained-to-completion requests must leave complete span sets too
    # (REJECTED ones never reach a validated terminal; they are skipped)
    all_reqs.extend(r for r in d_reqs if r.state == "finished")
    assert eng4.pool.num_used == 0 and eng4.page_accounting_ok()
    assert eng4._closed, "drain did not close the engine"
    snap = mx.snapshot()
    assert snap["serving/drains"]["value"] >= 1
    assert snap["serving/drained_requests"]["value"] >= 2
    assert snap["serving/drain_rejected"]["value"] >= 3  # 2 shed + 1 typed
    # span-set validation over every terminal request of the drill, plus
    # the written Chrome trace itself (the artifact a human opens)
    spans = tracer.stop_tracing()
    digests = strace.validate_request_spans(spans, all_reqs)
    assert len(digests) == len(all_reqs), (len(digests), len(all_reqs))
    assert digests[late.trace_id]["admitted"] is False
    assert digests[failed_req.trace_id]["state"] == "failed"
    admitted = sum(1 for d in digests.values() if d["admitted"])
    by_slot = strace.slot_assignments_from_spans(spans)
    assert sum(len(v) for v in by_slot.values()) == admitted, by_slot
    trace_path = os.path.join(tempfile.gettempdir(),
                              "serve_bench_trace_%d.json" % os.getpid())
    tracer.save_chrome_trace(trace_path, spans)
    # --- ragged paged-attention kernel A/B through the REAL bench path ---
    # (interpret mode on CPU: parity/provenance mechanics, not perf). The
    # kernel leg's digest must carry per-kernel provenance, and the gather
    # legs must stay pinned to the gather path regardless of the flag env.
    from paddle_tpu.flags import flags as _flags

    prev_flag = _flags.paged_attention_kernel
    res = serve_bench(n_requests=4, slots=2, vocab=64, n_layer=2,
                      d_model=32, n_head=2, max_seq=64, page_size=8,
                      max_prompt=12, max_new_hi=5, decode_fuse=1,
                      kernel="paged")
    assert _flags.paged_attention_kernel == prev_flag, "flag not restored"
    kleg = res["continuous_paged_kernel"]
    assert "error" not in kleg, kleg
    assert kleg["decode_kernel"] == "paged", kleg
    assert kleg["decode_kernel_source"] in ("tuned", "shipped", "default")
    assert res["continuous_paged"]["decode_kernel"] == "gather"
    assert res["static_padded"]["decode_kernel"] == "gather"
    assert res["kernel_vs_gather"]["qps_ratio"] > 0
    # same greedy stream both ways -> the kernel leg generates exactly the
    # gather baseline's token count (token-level stream parity is pinned
    # down in tests/test_paged_attention.py)
    assert kleg["tokens"] == res["continuous_paged"]["tokens"], (
        kleg["tokens"], res["continuous_paged"]["tokens"])
    # --- calibrated int8 KV pages: decode parity + the 2x capacity win ---
    # the bench's own int8 leg first (it calibrated from the fp leg's
    # pages and served with DOUBLE the page budget): the gate must have
    # actually taken (paged-int8 layout, not a silent fp fallback), the
    # greedy stream must generate the same token volume, and 2x the pages
    # must still cost fewer cache bytes than the fp pool
    i8leg = res["continuous_paged_int8_2x"]
    assert "error" not in i8leg, i8leg
    assert i8leg["mode"] == "continuous_paged-int8", i8leg["mode"]
    assert i8leg["tokens"] == res["continuous_paged"]["tokens"], (
        i8leg["tokens"], res["continuous_paged"]["tokens"])
    assert i8leg["cache_bytes"] < res["continuous_paged"]["cache_bytes"], (
        i8leg["cache_bytes"], res["continuous_paged"]["cache_bytes"])
    assert res["int8_2x_vs_fp"]["token_parity"], res["int8_2x_vs_fp"]
    # then logits-level parity: the SAME greedy stream through fp vs
    # calibrated int8 pages with per-token logits captured — the decode
    # outputs must agree within quantization tolerance, token for token
    from paddle_tpu.monitor import numerics as _num

    mc = model.cfg
    prompts = [list(rng.randint(0, 64, int(n))) for n in (6, 11, 17)]
    eng_fp = serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=64, collect_logits=True))
    p_reqs = [eng_fp.submit(p, 6) for p in prompts]
    eng_fp.run()
    k_amax = float(np.abs(np.asarray(eng_fp._cache["k"])).max())
    v_amax = float(np.abs(np.asarray(eng_fp._cache["v"])).max())
    tbl = os.path.join(tempfile.mkdtemp(prefix="serve_calib_"),
                       "calib.json")
    _num.record_kv_calibration(
        _num.kv_fingerprint(mc.n_layer, mc.n_head, mc.d_head, mc.dtype),
        k_amax, v_amax, path=tbl)
    prev_tbl = os.environ.get("PADDLE_TPU_NUMERICS_TABLE")
    os.environ["PADDLE_TPU_NUMERICS_TABLE"] = tbl
    try:
        eng_i8 = serving.ServingEngine(model, serving.ServingConfig(
            slots=2, page_size=8, max_seq=64, num_pages=32,
            collect_logits=True, kv_dtype="int8"))
        assert eng_i8.cache_ops.layout == "paged-int8", \
            "calibration gate fell back to fp pages"
        q_reqs = [eng_i8.submit(p, 6) for p in prompts]
        eng_i8.run()
    finally:
        if prev_tbl is None:
            os.environ.pop("PADDLE_TPU_NUMERICS_TABLE", None)
        else:
            os.environ["PADDLE_TPU_NUMERICS_TABLE"] = prev_tbl
    i8_err = 0.0
    for rf, ri in zip(p_reqs, q_reqs):
        assert rf.tokens_out == ri.tokens_out, (rf.tokens_out, ri.tokens_out)
        lf = np.stack(eng_fp.captured_logits(rf))
        li = np.stack(eng_i8.captured_logits(ri))
        err = float(np.max(np.abs(lf - li)) / (np.max(np.abs(lf)) + 1e-9))
        i8_err = max(i8_err, err)
        assert err < 0.02, "int8 KV logits drifted %.4g rel" % err
    # 32 int8 pages vs 16 fp pages over identical geometry: 2x the
    # capacity in ~half the bytes (scale arrays included)
    fp_bytes = eng_fp.cache_ops.cache_bytes(eng_fp._cache)
    i8_bytes = eng_i8.cache_ops.cache_bytes(eng_i8._cache)
    assert eng_i8.cache_ops.num_pages == 2 * eng_fp.cache_ops.num_pages
    assert i8_bytes < fp_bytes, (i8_bytes, fp_bytes)
    eng_fp.close()
    eng_i8.close()
    # --- speculative decoding: the draft-verify fast path ----------------
    # the bench's own speculative leg first: it ran the SAME greedy
    # stream, so the equivalence theorem (serving/speculative.py) makes
    # token parity an invariant; the leg must also carry its provenance
    # (drafter kind, k, which tune-table layer supplied it)
    sleg = res["continuous_paged_speculative"]
    assert "error" not in sleg, sleg
    assert sleg["tokens"] == res["continuous_paged"]["tokens"], (
        sleg["tokens"], res["continuous_paged"]["tokens"])
    assert sleg["speculation"] >= 1 and sleg["spec_drafter"] == "ngram", sleg
    assert sleg["speculation_source"] in ("tuned", "shipped", "default")
    assert res["speculative_vs_plain"]["token_parity"], (
        res["speculative_vs_plain"])
    snap = mx.snapshot()
    for name in ("serving/spec_proposed_tokens",
                 "serving/spec_accepted_tokens",
                 "serving/spec_rejected_tokens", "serving/spec_drafts",
                 "serving/spec_verify_dispatches",
                 "serving/spec_accept_rate"):
        assert name in snap, "missing instrument %s" % name
    # then the acceptance story on a stream built to accept: repetitive
    # prompts the n-gram drafter predicts. Greedy speculative tokens must
    # be BIT-identical to the plain-decode twin, acceptance must be
    # positive, each verify dispatch must retire > 1 token on average,
    # and page accounting must be exact after every rollback.
    rep_rng = np.random.RandomState(11)
    rep = [(list(rep_rng.randint(0, 64, 3)) * 4, 14) for _ in range(5)]
    eng_plain = serving.ServingEngine(model, serving.ServingConfig(
        slots=4, page_size=8, max_seq=64))
    p_twins = [eng_plain.submit(p, m) for p, m in rep]
    eng_plain.run()
    c0 = mx.snapshot()
    eng_spec = serving.ServingEngine(model, serving.ServingConfig(
        slots=4, page_size=8, max_seq=64, speculation=4))
    assert eng_spec.stats()["speculation"] == 4
    assert eng_spec.stats()["speculation_source"] == "explicit"
    s_twins = [eng_spec.submit(p, m) for p, m in rep]
    eng_spec.run()
    c1 = mx.snapshot()
    for a, b in zip(p_twins, s_twins):
        assert a.tokens_out == b.tokens_out, (a.tokens_out, b.tokens_out)
    assert eng_spec.page_accounting_ok() and eng_spec.pool.num_used == 0
    spec_prop = (c1["serving/spec_proposed_tokens"]["value"]
                 - c0["serving/spec_proposed_tokens"]["value"])
    spec_acc = (c1["serving/spec_accepted_tokens"]["value"]
                - c0["serving/spec_accepted_tokens"]["value"])
    spec_disp = (c1["serving/decode_dispatches"]["value"]
                 - c0["serving/decode_dispatches"]["value"])
    spec_toks = sum(len(r.tokens_out) for r in s_twins)
    assert spec_acc > 0 and spec_prop >= spec_acc, (spec_acc, spec_prop)
    spec_tpd = spec_toks / max(1.0, spec_disp)
    assert spec_tpd > 1.0, (spec_toks, spec_disp)
    eng_plain.close()
    eng_spec.close()
    # --- run-ledger + perf-gate mechanics on a throwaway ledger ----------
    # both kernel variants land as configs in one serve_bench record, and
    # a steady ledger of them gates NEUTRAL/IMPROVED (never REGRESSED)
    from paddle_tpu.monitor import runlog
    from tools import perf_gate

    led = os.path.join(tempfile.mkdtemp(prefix="serve_ledger_"),
                       "ledger.jsonl")
    prev_env = os.environ.get("PADDLE_TPU_RUN_LEDGER")
    os.environ["PADDLE_TPU_RUN_LEDGER"] = led
    try:
        configs = {"serve_" + leg: _ledger_fields(res[leg])
                   for leg in ("continuous_paged", "static_padded",
                               "continuous_paged_kernel",
                               "continuous_paged_int8_2x",
                               "continuous_paged_speculative")}
        for _ in range(5):
            rec = runlog.record_run("serve_bench", configs)
        assert rec.get("ledger_path") == led, rec.get("ledger_path")
        assert len(runlog.read_ledger(led)) == 5
        code, verdicts = perf_gate.check_ledger(path=led, quiet=True)
        assert code == 0, "perf gate flagged identical runs: exit %d" % code
        assert verdicts, "no verdicts from a 5-record ledger"
        bad = [v for v in verdicts
               if v.verdict not in ("NEUTRAL", "IMPROVED")]
        assert not bad, bad
    finally:
        if prev_env is None:
            os.environ.pop("PADDLE_TPU_RUN_LEDGER", None)
        else:
            os.environ["PADDLE_TPU_RUN_LEDGER"] = prev_env
    print("serve_bench selftest: OK (%.1fs)  %d requests traced; "
          "kernel leg %s/%s; int8 KV parity err %.2g with 2x pages "
          "%dB <= fp %dB; spec leg k=%d %s/%s accept %.0f/%.0f "
          "(%.2f tok/dispatch, bit-parity); trace: %s"
          % (time.perf_counter() - t0, len(digests),
             kleg["decode_kernel"], kleg["decode_kernel_source"],
             i8_err, i8_bytes, fp_bytes,
             sleg["speculation"], sleg["spec_drafter"],
             sleg["speculation_source"], spec_acc, spec_prop, spec_tpd,
             trace_path))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if argv and argv[0] == "--selftest":
        return selftest()
    _install_sigterm_drain()  # bench mode: SIGTERM drains, never mid-decode
    kw = {}
    it = iter(argv)
    for a in it:
        key = a.lstrip("-").replace("-", "_")
        if key == "kernel":
            val = next(it)
            if val not in ("auto", "gather", "paged"):
                print("--kernel must be auto|gather|paged, got %r" % val,
                      file=sys.stderr)
                return 2
            kw["kernel"] = val
            continue
        if key not in ("requests", "slots", "seed", "decode_fuse"):
            print("unknown flag %r" % a, file=sys.stderr)
            return 2
        kw["n_requests" if key == "requests" else key] = int(next(it))
    res = serve_bench(**kw)
    try:
        # one run-ledger record per serve bench (armed via
        # PADDLE_TPU_RUN_LEDGER); the run_id rides the printed JSON so
        # ledger <-> telemetry <-> trace artifacts join on it
        from paddle_tpu.monitor import runlog

        configs = {}
        for leg in ("continuous_paged", "static_padded",
                    "continuous_paged_kernel", "continuous_paged_int8_2x",
                    "continuous_paged_speculative"):
            if isinstance(res.get(leg), dict) and "error" not in res[leg]:
                configs["serve_" + leg] = _ledger_fields(res[leg])
        runlog.record_run("serve_bench", configs)
        res.update(runlog.tail_info())
    except Exception as e:
        res["run_ledger_error"] = repr(e)[:80]
    print(json.dumps(res, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
