"""Fleet-serving bench driver + CI smoke.

    python -m tools.fleet_bench --selftest
        <30s, JAX_PLATFORMS=cpu, in-process + subprocess replicas:
        exercises the full fleet contract — exactly-once accounting,
        prefix affinity, health-aware routing (degraded replicas get no
        new traffic), SIGKILL requeue with bit-identical seeded replay,
        rolling restart with zero rejected-by-bug, near-linear QPS
        scaling 1 -> 4 sim replicas over the worker protocol, a real
        ServingEngine prefix-cache leg (reduced prefill dispatches vs
        cold), the disaggregation legs — 2-prefill/2-decode beats 4
        uniform on a bursty mixed stream (bit-identical tokens), a
        remote prefix hit served by shipping KV pages across replicas
        (real engines, binary page frames), SIGKILL mid-migration with
        exactly-once accounting + unkilled-twin replay — the fleet/*
        registry, and the run-ledger/perf-gate mechanics. The
        smoke-gate entry (ROADMAP).

    python -m tools.fleet_bench [--requests N] [--replicas "1,2,4"]
                                [--step-ms MS] [--slots S]
        Fleet bench on this host: per-replica-count QPS over the
        process-worker protocol (sleep-based sim engines modeling a
        device-bound accelerator — the router/protocol scaling is the
        thing measured), plus a real-engine shared-system-prompt leg
        (cold vs warm prefix cache). Prints JSON (per-count QPS, fleet
        snapshot, prefix hit rate); appends one run-ledger record per
        replica count via monitor.runlog (armed by PADDLE_TPU_RUN_LEDGER)
        so tools/perf_gate --check gates fleet QPS like every other bench.

Scaling is measured with SIM engines in REAL worker processes: each sim
step sleeps its ``step_ms`` like a host blocked on a device dispatch, so
replicas overlap wall-clock the way TPU replicas would, even on a 1-core
CI host where real compute cannot parallelize. Every correctness leg
(kill, requeue, prefix, restart) runs real code paths — only the decode
arithmetic is simulated in the scaling leg.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.monitor.metrics import sorted_percentile  # noqa: E402


def _sim_spec(slots: int, step_ms: float, **sim_kw) -> dict:
    return {"engine": "sim",
            "sim": dict({"slots": slots, "step_ms": step_ms}, **sim_kw)}


def _tiny_real_spec(page_size: int = 8) -> dict:
    """A real ServingEngine small enough for CPU-sim workers, with the
    prefix cache armed — the migration legs' engine."""
    return {"engine": "real",
            "model": {"vocab_size": 64, "n_layer": 1, "d_model": 16,
                      "n_head": 2, "max_seq": 64},
            "serving": {"slots": 2, "page_size": page_size, "max_seq": 64,
                        "num_pages": 48, "prefix_cache_pages": 16}}


def _mixed_stream(n_requests: int, prompt_len: int, max_new: int):
    """The bursty mixed stream both disagg legs drive: distinct LONG
    prompts (prefill-heavy — each forces a full prompt ingest) woven with
    SHORT follow-ups (decode-heavy — they keep decode slots busy, so a
    uniform replica's prefills land mid-decode and pay the mixed-batch
    interference). Seeds are explicit so the two fleet shapes must
    produce bit-identical streams."""
    reqs = []
    for i in range(n_requests):
        if i % 2 == 0:
            prompt = [(i * 131 + t) % 251 + 1 for t in range(prompt_len)]
        else:
            prompt = [3, 5, i % 7]
        reqs.append((prompt, max_new, 1000 + i))
    return reqs


def run_scaling_leg(n_replicas: int, n_requests: int = 96,
                    step_ms: float = 4.0, slots: int = 4,
                    max_new: int = 16, telemetry_base: str = None,
                    trace_dir: str = None, event_log: str = None) -> dict:
    """Drive ``n_requests`` through ``n_replicas`` process workers (sim
    engines); returns the throughput digest the ledger gates.
    ``trace_dir``/``event_log`` arm the fleet observability plane for
    the leg (they also fall back to the PADDLE_TPU_FLEET_* env knobs via
    FleetConfig)."""
    from paddle_tpu.fleet import FleetConfig, Router

    router = Router(FleetConfig(
        replicas=n_replicas, mode="process", affinity="round_robin",
        engine_spec=_sim_spec(slots, step_ms), max_outstanding=slots * 2,
        telemetry_base=telemetry_base, trace_dir=trace_dir,
        event_log=event_log))
    try:
        t0 = time.perf_counter()
        frs = [router.submit([1, 2, i % 13], max_new)
               for i in range(n_requests)]
        ok = router.wait_all(120.0)
        dt = time.perf_counter() - t0
        acc = router.accounting()
        bad = {k: v for k, v in acc.items() if v != "finished"}
        assert ok and not bad, "scaling leg dropped requests: %s" % bad
        lat = sorted(f.latency_s * 1e3 for f in frs)
        snap = router.snapshot()
        out = {"replicas": n_replicas, "requests": n_requests,
               "qps": round(n_requests / dt, 3),
               "tokens_per_sec": round(
                   sum(len(f.tokens) for f in frs) / dt, 1),
               "p50_ms": round(sorted_percentile(lat, 50), 3),
               "p99_ms": round(sorted_percentile(lat, 99), 3),
               "wall_s": round(dt, 3),
               "streams": [f.tokens for f in frs],
               "snapshot": snap}
        # armed observability artifacts ride the digest (and the tail /
        # ledger record), so a bench run's trace merges and rings tail
        # without spelunking for paths
        if router.cfg.trace_dir:
            out["trace_dir"] = router.cfg.trace_dir
        if router.cfg.event_log:
            out["event_log"] = router.cfg.event_log
        if telemetry_base:
            out["telemetry_dirs"] = [
                os.path.join(telemetry_base, "replica_%d" % i)
                for i in range(n_replicas)]
        return out
    finally:
        router.close()


def run_prefix_leg(n_requests: int = 8, prefix_pages: int = 8) -> dict:
    """Shared-system-prompt stream through a real ServingEngine, cold vs
    warm prefix cache: the warm pass must serve the shared prefix from
    cached KV pages (fewer prefill dispatches, hits > 0) and generate the
    SAME tokens."""
    from paddle_tpu.fleet import metrics as fm
    from paddle_tpu.models.decoder_lm import DecoderConfig, DecoderLM
    from paddle_tpu.serving import metrics as sm
    from paddle_tpu.serving.engine import ServingConfig, ServingEngine

    mcfg = DecoderConfig(vocab_size=64, n_layer=1, d_model=16, n_head=2,
                         max_seq=64)
    model = DecoderLM(mcfg, seed=7)
    system_prompt = list(range(1, 17))  # 16 tokens = 2 pages of 8

    def drive(cache_pages: int) -> tuple:
        eng = ServingEngine(model, ServingConfig(
            slots=2, page_size=8, max_seq=64, num_pages=32,
            prefix_cache_pages=cache_pages))
        p0 = sm.PREFILL_COUNT.value
        outs = []
        t0 = time.perf_counter()
        for i in range(n_requests):
            req = eng.submit(system_prompt + [20 + i, 21 + i], 6,
                             temperature=0.7, seed=500 + i)
            eng.run()
            assert req.state == "finished", req
            outs.append(list(req.tokens_out))
        dt = time.perf_counter() - t0
        prefills = int(sm.PREFILL_COUNT.value - p0)
        assert eng.page_accounting_ok(), "page accounting broken"
        eng.drain(10.0)
        assert eng.pool.num_used == 0, "pages leaked through drain"
        return outs, prefills, dt

    h0, m0 = fm.PREFIX_HITS.value, fm.PREFIX_MISSES.value
    outs_cold, prefills_cold, _ = drive(0)
    outs_warm, prefills_warm, _ = drive(prefix_pages)
    hits = int(fm.PREFIX_HITS.value - h0)
    misses = int(fm.PREFIX_MISSES.value - m0)
    assert outs_warm == outs_cold, \
        "prefix-cache hits changed the generated streams"
    assert hits > 0, "warm pass produced no prefix hits"
    assert prefills_warm < prefills_cold, (prefills_warm, prefills_cold)
    return {"requests": n_requests,
            "prefill_dispatches_cold": prefills_cold,
            "prefill_dispatches_warm": prefills_warm,
            "prefix_hits": hits, "prefix_misses": misses,
            "hit_rate": round(hits / max(1, hits + misses), 3)}


def _fresh_health(router, index: int, timeout_s: float = 10.0) -> dict:
    """Ask replica ``index`` for a fresh health doc and pump until the
    answer (with the engine-level fields) lands in the router's cache."""
    router._replicas[index].health()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        router.pump()
        doc = router._health.get(index, {})
        if "page_accounting_ok" in doc:
            return doc
        time.sleep(0.002)
    raise AssertionError("no fresh health from replica %d" % index)


def run_disagg_leg(n_requests: int = 24, prompt_len: int = 97,
                   max_new: int = 6, step_ms: float = 1.0,
                   slots: int = 4) -> dict:
    """Disaggregation QPS leg (ISSUE 18 acceptance): the SAME bursty
    mixed stream through 4 uniform replicas and through a 2-prefill /
    2-decode fleet, sim engines in real worker processes. The sim cost
    model charges ``prefill_ms_per_token`` per unknown prompt token and
    multiplies it by ``interference`` when the ingest lands on a replica
    with decodes in flight — the TPU mixed-batch stall. Prefill-role
    replicas run one-token internal jobs that finish at admission and
    never interleave with decodes, so the disagg fleet pays prompt
    ingestion at 1x and ships the KV pages to a decode replica, while
    every long prompt in the uniform fleet stalls a decoding batch at
    ``interference``x. Streams must be bit-identical; QPS ratio > 1.0."""
    from paddle_tpu.fleet import FleetConfig, Router
    from paddle_tpu.fleet import metrics as fm

    sim = dict(page_size=16, prefill_ms_per_token=0.4, interference=4.0)
    reqs = _mixed_stream(n_requests, prompt_len, max_new)

    def drive(cfg):
        router = Router(cfg)
        try:
            t0 = time.perf_counter()
            frs = [router.submit(p, m, temperature=0.6, seed=s)
                   for p, m, s in reqs]
            assert router.wait_all(120.0), router.accounting()
            dt = time.perf_counter() - t0
            acc = router.accounting()
            assert len(acc) == n_requests \
                and set(acc.values()) == {"finished"}, acc
            return [f.tokens for f in frs], dt, router.snapshot()
        finally:
            router.close()

    uni_streams, uni_dt, _ = drive(FleetConfig(
        replicas=4, mode="process", affinity="round_robin",
        engine_spec=_sim_spec(slots, step_ms, **sim),
        max_outstanding=slots * 2))
    mc0, mp0 = fm.MIGRATIONS_COMPLETED.value, fm.MIGRATED_PAGES.value
    dis_streams, dis_dt, snap = drive(FleetConfig(
        roles="2:2", mode="process", affinity="round_robin",
        engine_spec=_sim_spec(slots, step_ms, **sim),
        page_size=16, max_outstanding=slots * 2))
    migrations = int(fm.MIGRATIONS_COMPLETED.value - mc0)
    assert dis_streams == uni_streams, \
        "disaggregation changed the generated streams"
    assert migrations > 0, "disagg fleet migrated nothing"
    assert snap["roles"]["prefill"] == 2 \
        and snap["roles"]["decode"] == 2, snap["roles"]
    ratio = (n_requests / dis_dt) / (n_requests / uni_dt)
    assert ratio > 1.0, \
        "2P/2D disagg did not beat 4 uniform: %.2fx" % ratio
    return {"requests": n_requests,
            "qps_uniform_4": round(n_requests / uni_dt, 3),
            "qps_disagg_2p2d": round(n_requests / dis_dt, 3),
            "qps_ratio": round(ratio, 3),
            "migrations": migrations,
            "migrated_pages": int(fm.MIGRATED_PAGES.value - mp0)}


def run_remote_prefix_leg() -> dict:
    """Fleet-wide prefix cache leg: two REAL tiny engines in worker
    processes; request 1 prefills on its replica, then — with that owner
    refusing new traffic — the identical request 2 must land on the
    OTHER replica, served by shipping the owner's KV pages across the
    pipe (binary page frames): a remote prefix hit, zero prefill
    dispatches on the destination, bit-identical stream."""
    from paddle_tpu.fleet import FleetConfig, Router
    from paddle_tpu.fleet import metrics as fm

    spec = _tiny_real_spec(page_size=8)
    prompt = [(7 * t) % 60 + 1 for t in range(19)]  # 2 full pages + tail
    h0, s0 = fm.REMOTE_HITS.value, fm.REMOTE_SHIPS.value
    router = Router(FleetConfig(
        replicas=2, mode="process", affinity="round_robin",
        engine_spec=spec, fleet_prefix=True, page_size=8,
        max_outstanding=4))
    try:
        f1 = router.submit(prompt, 5, temperature=0.8, seed=11)
        assert router.wait_all(90.0), router.accounting()
        owner = f1.last_replica
        dst = 1 - owner
        # the owner stops accepting: the only route for the identical
        # request is the fleet index — ship owner pages to the peer
        router._replicas[owner].accepting = False
        f2 = router.submit(prompt, 5, temperature=0.8, seed=11)
        assert router.wait_all(90.0), router.accounting()
        assert f2.state == "finished" and f2.last_replica == dst, \
            (f2.state, f2.last_replica, owner)
        assert f2.tokens == f1.tokens, \
            "remote prefix hit changed the stream: %s vs %s" \
            % (f2.tokens, f1.tokens)
        hits = int(fm.REMOTE_HITS.value - h0)
        ships = int(fm.REMOTE_SHIPS.value - s0)
        assert hits >= 1 and ships >= 1, (hits, ships)
        hd = _fresh_health(router, dst)
        assert hd["page_accounting_ok"], hd
        assert hd.get("prefills", 0) == 0 and hd.get("resumes", 0) >= 1, \
            "destination did not resume from shipped pages: %s" % hd
    finally:
        router.close()
    # cold twin: the same request on a fresh single replica must produce
    # the same stream (the migrated path changed routing, not tokens) —
    # and it costs a prefill dispatch the remote hit avoided
    twin = Router(FleetConfig(replicas=1, mode="process",
                              engine_spec=spec, max_outstanding=4))
    try:
        ft = twin.submit(prompt, 5, temperature=0.8, seed=11)
        assert twin.wait_all(90.0), twin.accounting()
        assert ft.tokens == f1.tokens, (ft.tokens, f1.tokens)
        ht = _fresh_health(twin, 0)
        assert ht.get("prefills", 0) >= 1, ht
    finally:
        twin.close()
    return {"remote_hits": hits, "remote_ships": ships,
            "dst_prefills": hd.get("prefills"),
            "dst_resumes": hd.get("resumes"),
            "cold_prefills": ht.get("prefills")}


def _selftest_migration_kill() -> None:
    """SIGKILL mid-migration (ISSUE 18 acceptance): a 1-prefill/2-decode
    process fleet loses a migration-involved worker to SIGKILL while KV
    pages are in flight. Every request must still reach exactly one
    terminal outcome (migrations fail closed: the carried requests fall
    back to a cold prefill), the replay must be bit-identical to an
    unkilled twin, page accounting must hold on every surviving replica,
    and the kill -> migration-failed -> recovery story must be readable
    from the fleet event log under one run_id."""
    from paddle_tpu.fleet import FleetConfig, Router
    from paddle_tpu.fleet import metrics as fm
    from paddle_tpu.fleet.events import read_events

    sim = dict(page_size=16, prefill_ms_per_token=1.0, interference=4.0)
    reqs = _mixed_stream(10, 97, 6)

    def cfg(elog=None):
        return FleetConfig(roles="1:2", mode="process",
                           affinity="round_robin", page_size=16,
                           engine_spec=_sim_spec(4, 1.0, **sim),
                           max_outstanding=8, event_log=elog)

    mf0 = fm.MIGRATIONS_FAILED.value
    with tempfile.TemporaryDirectory() as td:
        elog = os.path.join(td, "events.jsonl")
        router = Router(cfg(elog))
        try:
            frs = [router.submit(p, m, temperature=0.6, seed=s)
                   for p, m, s in reqs]
            deadline = time.monotonic() + 30.0
            victim = None
            while time.monotonic() < deadline:
                router.pump()
                if router._migrations:
                    m = next(iter(router._migrations.values()))
                    # the destination once pages are in flight, else the
                    # source mid-prefill: either end dies mid-migration
                    victim = m.dst if m.dst is not None else m.src
                    break
                time.sleep(0.001)
            assert victim is not None, "no migration ever started"
            router._replicas[victim].kill()  # SIGKILL, no goodbye
            assert router.wait_all(90.0), router.accounting()
            acc = router.accounting()
            assert len(acc) == len(reqs) \
                and set(acc.values()) == {"finished"}, \
                "not exactly-once under mid-migration SIGKILL: %s" % acc
            assert fm.MIGRATIONS_FAILED.value > mf0, \
                "the killed replica's migration did not fail closed"
            for i, rep in enumerate(router._replicas):
                if rep.alive:
                    assert _fresh_health(router, i)["page_accounting_ok"], \
                        "page accounting broken on replica %d" % i
        finally:
            router.close()
        evs = read_events(elog)
        kinds = [e["kind"] for e in evs]
        for needed in ("migration_start", "kill_detected",
                       "migration_failed", "spawn"):
            assert needed in kinds, "event log missing %r: %s" \
                % (needed, sorted(set(kinds)))
        rids = {e["run_id"] for e in evs}
        assert len(rids) == 1, \
            "kill story fragmented across run_ids: %s" % rids

    twin = Router(cfg())
    try:
        frs_t = [twin.submit(p, m, temperature=0.6, seed=s)
                 for p, m, s in reqs]
        assert twin.wait_all(90.0), twin.accounting()
        assert [f.tokens for f in frs] == [f.tokens for f in frs_t], \
            "mid-migration SIGKILL replay diverged from the unkilled twin"
    finally:
        twin.close()


# -- selftest -----------------------------------------------------------------
def _selftest_mechanics() -> None:
    """In-process sim fleet: exactly-once, affinity, health-aware
    dispatch."""
    from paddle_tpu.fleet import (FleetConfig, Router, SimConfig, SimEngine,
                                  prefix_key)
    from paddle_tpu.fleet import metrics as fm

    engines = {}

    def factory(i):
        engines[i] = SimEngine(SimConfig(slots=2))
        return engines[i]

    router = Router(FleetConfig(replicas=3, mode="inprocess",
                                affinity="prefix", affinity_tokens=4,
                                engine_factory=factory))
    # prefix affinity: same window -> same replica (before any degradation)
    window = [5, 6, 7, 8]
    expect = int(prefix_key(window)[:8], 16) % 3
    frs = [router.submit(window + [i], 4) for i in range(6)]
    assert router.wait_all(20.0)
    assert all(f.state == "finished" for f in frs)
    assert all(f.last_replica == expect for f in frs), \
        "prefix affinity scattered a cohort"
    # health-aware: degrade that replica; the cohort must route elsewhere
    engines[expect].force_degraded = True
    frs2 = [router.submit(window + [90 + i], 4) for i in range(4)]
    assert router.wait_all(20.0)
    assert all(f.state == "finished" for f in frs2)
    assert all(f.last_replica != expect for f in frs2), \
        "a degraded replica was fed new traffic"
    # exactly-once: every id has exactly one terminal state
    acc = router.accounting()
    assert len(acc) == 10 and set(acc.values()) == {"finished"}, acc
    dup0 = fm.DUPLICATE_RESULTS.value
    router.close()
    assert fm.DUPLICATE_RESULTS.value == dup0


def _selftest_kill_replay() -> None:
    """In-process SIGKILL analog: requeue + bit-identical seeded replay
    vs an unkilled twin."""
    from paddle_tpu.fleet import FleetConfig, Router, SimConfig, SimEngine
    from paddle_tpu.fleet import metrics as fm

    def cfg(n):
        return FleetConfig(replicas=n, mode="inprocess",
                           affinity="round_robin",
                           engine_factory=lambda i: SimEngine(
                               SimConfig(slots=1)))

    req0 = fm.REQUEUED.value
    router = Router(cfg(2))
    frs = [router.submit([9, 9, i], 8, temperature=0.7) for i in range(8)]
    for _ in range(3):
        router.pump()
    router._replicas[0].kill()  # mid-traffic loss
    assert router.wait_all(20.0)
    acc = router.accounting()
    assert set(acc.values()) == {"finished"}, "silent drop/failure: %s" % acc
    assert fm.REQUEUED.value > req0, "kill lost no in-flight work?"
    twin = Router(cfg(1))
    frs_t = [twin.submit([9, 9, i], 8, temperature=0.7) for i in range(8)]
    assert twin.wait_all(20.0)
    assert [f.tokens for f in frs] == [f.tokens for f in frs_t], \
        "requeued replay diverged from the unkilled twin"
    router.close()
    twin.close()


def _selftest_process_kill() -> None:
    """The real thing: SIGKILL a worker process mid-traffic; exactly-once
    + bit-identical replay must hold across the pipe protocol."""
    from paddle_tpu.fleet import FleetConfig, Router
    from paddle_tpu.fleet import metrics as fm

    spec = _sim_spec(slots=2, step_ms=3.0)
    router = Router(FleetConfig(replicas=2, mode="process",
                                affinity="round_robin", engine_spec=spec,
                                max_outstanding=4))
    frs = [router.submit([7, i], 12, temperature=0.5) for i in range(16)]
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline \
            and not router._replicas[0].inflight:
        router.pump()
        time.sleep(0.002)
    assert router._replicas[0].inflight, "no traffic reached the victim"
    r0 = fm.REPLICA_RESTARTS.value
    router._replicas[0].kill()
    assert router.wait_all(60.0)
    acc = router.accounting()
    assert set(acc.values()) == {"finished"}, "silent drop: %s" % acc
    assert fm.REPLICA_RESTARTS.value > r0, "dead worker not respawned"
    twin = Router(FleetConfig(replicas=1, mode="process", engine_spec=spec,
                              max_outstanding=4))
    frs_t = [twin.submit([7, i], 12, temperature=0.5) for i in range(16)]
    assert twin.wait_all(60.0)
    assert [f.tokens for f in frs] == [f.tokens for f in frs_t], \
        "SIGKILL replay diverged from the unkilled twin"
    router.close()
    twin.close()


def _selftest_rolling_restart() -> None:
    """Rolling restart under traffic: zero rejected-by-bug terminal
    states, all requests finish."""
    from paddle_tpu.fleet import FleetConfig, Router

    spec = _sim_spec(slots=2, step_ms=2.0)
    router = Router(FleetConfig(replicas=2, mode="process", engine_spec=spec,
                                max_outstanding=4))
    frs = [router.submit([5, i], 10) for i in range(12)]
    for _ in range(10):
        router.pump()
        time.sleep(0.002)
    router.rolling_restart(15.0)
    assert router.wait_all(60.0)
    acc = router.accounting()
    assert set(acc.values()) == {"finished"}, \
        "rolling restart rejected/lost requests: %s" % acc
    assert all(f.tokens for f in frs)
    router.close()


def _selftest_fleet_slo() -> None:
    """Fleet-SLO drill (ISSUE 16 acceptance): a per-replica latency fault
    (installed through the ordinary PADDLE_TPU_FAULT_PLAN grammar via
    ``spec_overrides``) breaches the p99 spec at BOTH scopes — replica 0
    alone and the fleet aggregate — ticks ``slo/breaches``, degrades
    replica 0 in the snapshot, and journals the breach in the event log
    joined to the spawns by run_id."""
    from paddle_tpu.fleet import FleetConfig, Router
    from paddle_tpu.fleet.events import read_events
    from paddle_tpu.monitor import metrics as mx
    from paddle_tpu.monitor.slo import parse_slos

    # pin the workers' export interval above the run length: each worker
    # ring then holds exactly ONE sample — the final partial interval
    # flushed at release — so the close()-time evaluation judges the whole
    # run's latency distribution deterministically
    prev = os.environ.get("PADDLE_TPU_TELEMETRY_INTERVAL_S")
    os.environ["PADDLE_TPU_TELEMETRY_INTERVAL_S"] = "60"
    try:
        with tempfile.TemporaryDirectory() as td:
            base = os.path.join(td, "tele")
            elog = os.path.join(td, "events.jsonl")
            b0 = mx.counter("slo/breaches").value
            router = Router(FleetConfig(
                replicas=2, mode="process", affinity="round_robin",
                engine_spec=_sim_spec(slots=2, step_ms=2.0),
                max_outstanding=4, telemetry_base=base, event_log=elog,
                slos=parse_slos("serving/request_latency_ms:p99<=150"),
                spec_overrides={0: {
                    "fault_plan": "serving.decode@1=latency:999:60"}}))
            try:
                frs = [router.submit([3, i], 8) for i in range(10)]
                assert router.wait_all(60.0), router.accounting()
                assert all(f.state == "finished" for f in frs)
            finally:
                router.close()  # workers flush final samples -> SLO pass

            assert mx.counter("slo/breaches").value > b0, \
                "faulted replica breached no SLO"
            snap = router.snapshot()
            slo = snap["slo"]
            assert slo["specs"] == ["serving/request_latency_ms:p99"], slo
            assert 0 in slo["breached_replicas"], slo
            assert slo["fleet_breaches"] >= 1 and slo["fleet_breach"], slo
            r0 = next(r for r in snap["replicas"]
                      if r["name"] == "replica-0")
            assert r0["health"]["status"] == "degraded" \
                and r0["health"].get("slo_breached"), r0
            r1 = next(r for r in snap["replicas"]
                      if r["name"] == "replica-1")
            assert not r1["health"].get("slo_breached"), \
                "the unfaulted replica was marked breached: %s" % r1

            evs = read_events(elog)
            breaches = [e for e in evs if e["kind"] == "slo_breach"]
            scopes = {e.get("scope") for e in breaches}
            assert {"replica", "fleet"} <= scopes, breaches
            assert any(e.get("replica") == 0 for e in breaches), breaches
            spawn_rids = {e["run_id"] for e in evs if e["kind"] == "spawn"}
            assert len(spawn_rids) == 1 and all(
                e["run_id"] in spawn_rids for e in breaches), \
                "breach events not joinable to spawns by run_id"
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_TELEMETRY_INTERVAL_S", None)
        else:
            os.environ["PADDLE_TPU_TELEMETRY_INTERVAL_S"] = prev


def selftest() -> int:
    t0 = time.perf_counter()
    from paddle_tpu.monitor import metrics as mx

    mx.enable()
    _selftest_mechanics()
    _selftest_kill_replay()
    _selftest_process_kill()
    _selftest_rolling_restart()
    _selftest_fleet_slo()

    # scaling: 1 vs 4 sim-engine workers over the real worker protocol.
    # identical streams at every width (seeded, position-keyed), and >=3x
    # QPS at 4 replicas (ISSUE 15 acceptance bar)
    with tempfile.TemporaryDirectory() as td:
        leg1 = run_scaling_leg(1, telemetry_base=os.path.join(td, "f1"))
        leg4 = run_scaling_leg(4, telemetry_base=os.path.join(td, "f4"))
        scale = leg4["qps"] / leg1["qps"]
        assert leg1["streams"] == leg4["streams"], \
            "token streams depend on replica count"
        assert scale >= 3.0, \
            "QPS scaling 1->4 replicas = %.2fx (< 3.0x)" % scale
        snap = leg4["snapshot"]
        assert len(snap["replicas"]) == 4
        assert all(r["completed"] > 0 for r in snap["replicas"]), \
            "a replica served nothing: %s" % snap["replicas"]
        assert all(r["p99_ms"] is not None for r in snap["replicas"])
        # per-replica telemetry rings, merged into one fleet view: every
        # worker flushes a final sample when the router closes it
        from paddle_tpu.fleet import aggregate_telemetry

        tele = aggregate_telemetry(os.path.join(td, "f4"))
        assert len(tele) == 4, "expected 4 replica rings: %s" % list(tele)
        assert all(v["samples"] >= 1 for v in tele.values()), tele
        # armed legs surface their artifact paths in the digest (the
        # bench tail + ledger extra are built from these)
        assert len(leg4["telemetry_dirs"]) == 4, leg4

    prefix = run_prefix_leg()

    # ISSUE 18: disaggregation beats uniform, remote prefix hits serve
    # across replicas, SIGKILL mid-migration stays exactly-once
    disagg = run_disagg_leg()
    remote = run_remote_prefix_leg()
    _selftest_migration_kill()

    # fleet/* registry: the full instrument set must be live
    import paddle_tpu.fleet.metrics  # noqa: F401

    reg = mx.snapshot()
    for name in ("fleet/submitted", "fleet/routed", "fleet/requeued",
                 "fleet/completed", "fleet/replica_restarts",
                 "fleet/queue_depth", "fleet/prefix_cache/hits",
                 "fleet/prefix_cache/evictions",
                 "fleet/prefix_cache/poisoned_skipped",
                 "fleet/migrations_started", "fleet/migrations_completed",
                 "fleet/migrations_failed", "fleet/migrated_pages",
                 "fleet/migration_ms", "fleet/prefix_cache/remote_hits",
                 "fleet/prefix_cache/remote_misses",
                 "fleet/prefix_cache/remote_ships"):
        assert name in reg, "missing fleet instrument %s" % name

    # run-ledger + perf-gate mechanics on a throwaway ledger: one config
    # per replica count, steady records gate NEUTRAL/IMPROVED
    from paddle_tpu.monitor import runlog
    from tools import perf_gate

    led = os.path.join(tempfile.mkdtemp(prefix="fleet_ledger_"),
                       "ledger.jsonl")
    prev_env = os.environ.get("PADDLE_TPU_RUN_LEDGER")
    os.environ["PADDLE_TPU_RUN_LEDGER"] = led
    try:
        configs = {}
        for leg in (leg1, leg4):
            configs["fleet_r%d" % leg["replicas"]] = {
                k: v for k, v in leg.items()
                if isinstance(v, (int, float))}
        configs["fleet_prefix"] = {k: v for k, v in prefix.items()
                                   if isinstance(v, (int, float))}
        configs["fleet_disagg"] = {k: v for k, v in disagg.items()
                                   if isinstance(v, (int, float))}
        configs["fleet_remote_prefix"] = {
            k: v for k, v in remote.items() if isinstance(v, (int, float))}
        for _ in range(5):
            rec = runlog.record_run("fleet_bench", configs)
        assert rec.get("ledger_path") == led
        assert len(runlog.read_ledger(led)) == 5
        code, verdicts = perf_gate.check_ledger(path=led, quiet=True)
        assert code == 0, "perf gate flagged identical runs: exit %d" % code
        bad = [v for v in verdicts
               if v.verdict not in ("NEUTRAL", "IMPROVED")]
        assert not bad, bad
    finally:
        if prev_env is None:
            os.environ.pop("PADDLE_TPU_RUN_LEDGER", None)
        else:
            os.environ["PADDLE_TPU_RUN_LEDGER"] = prev_env

    print("fleet_bench selftest: OK (%.1fs)  scaling 1->4 = %.2fx "
          "(qps %.0f -> %.0f); prefix hit_rate=%.2f prefills %d -> %d; "
          "disagg 2P/2D vs 4U = %.2fx (%d migrations, %d pages); "
          "remote prefix hits=%d (dst prefills=%d resumes=%d)"
          % (time.perf_counter() - t0, scale, leg1["qps"], leg4["qps"],
             prefix["hit_rate"], prefix["prefill_dispatches_cold"],
             prefix["prefill_dispatches_warm"], disagg["qps_ratio"],
             disagg["migrations"], disagg["migrated_pages"],
             remote["remote_hits"], remote["dst_prefills"],
             remote["dst_resumes"]))
    return 0


def fleet_bench(n_requests: int = 96, replica_counts=(1, 2, 4),
                step_ms: float = 4.0, slots: int = 4,
                telemetry_base: str = None) -> dict:
    """The bench body ``--selftest`` does NOT run: per-replica-count QPS
    legs + the real-engine prefix leg, as one JSON digest. The fleet
    observability env knobs arm the legs: PADDLE_TPU_FLEET_TRACE_DIR and
    a --telemetry-base get a per-leg subdir (each leg is its own fleet —
    one manifest/ring set per leg), PADDLE_TPU_FLEET_EVENTS is shared
    (the journal appends; legs are told apart by run_id + fleet_start)."""
    from paddle_tpu.monitor import metrics as mx

    mx.enable()
    res = {"host_cpus": os.cpu_count(), "step_ms": step_ms, "slots": slots}
    trace_base = (os.environ.get("PADDLE_TPU_FLEET_TRACE_DIR") or "").strip()
    legs = {}
    for n in replica_counts:
        name = "replicas_%d" % n
        leg = run_scaling_leg(
            n, n_requests=n_requests, step_ms=step_ms, slots=slots,
            telemetry_base=(os.path.join(telemetry_base, name)
                            if telemetry_base else None),
            trace_dir=(os.path.join(trace_base, name)
                       if trace_base else None))
        leg.pop("streams", None)  # bulky; identical across counts anyway
        legs[name] = leg
    res["scaling"] = legs
    obs = {}
    for key in ("trace_dir", "event_log", "telemetry_dirs"):
        got = {n: leg[key] for n, leg in legs.items() if key in leg}
        if got:
            obs[key] = got
    if obs:
        res["observability"] = obs
    base = legs.get("replicas_%d" % replica_counts[0])
    top = legs.get("replicas_%d" % replica_counts[-1])
    if base and top:
        res["qps_scale"] = round(top["qps"] / base["qps"], 3)
    res["prefix"] = run_prefix_leg()
    res["disagg"] = run_disagg_leg(step_ms=step_ms, slots=slots)
    res["remote_prefix"] = run_remote_prefix_leg()
    return res


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if argv and argv[0] == "--selftest":
        return selftest()
    kw = {}
    it = iter(argv)
    for a in it:
        key = a.lstrip("-").replace("-", "_")
        if key == "replicas":
            kw["replica_counts"] = tuple(
                int(x) for x in next(it).split(","))
        elif key == "requests":
            kw["n_requests"] = int(next(it))
        elif key == "step_ms":
            kw["step_ms"] = float(next(it))
        elif key == "slots":
            kw["slots"] = int(next(it))
        elif key == "telemetry_base":
            kw["telemetry_base"] = next(it)
        else:
            print("unknown flag %r" % a, file=sys.stderr)
            return 2
    res = fleet_bench(**kw)
    try:
        # one ledger record per replica count (plus the prefix leg), so
        # perf_gate --check gates fleet QPS per width like every other
        # bench kind (armed via PADDLE_TPU_RUN_LEDGER); when the
        # observability plane was armed, the artifact paths ride the
        # record's extra block so a regression's run_id leads straight to
        # its trace/rings/events
        from paddle_tpu.monitor import runlog

        obs = res.get("observability")
        for name, leg in res["scaling"].items():
            cfg = {k: v for k, v in leg.items()
                   if isinstance(v, (int, float))}
            leg_obs = {key: paths[name] for key, paths in (obs or {}).items()
                       if name in paths}
            runlog.record_run("fleet_bench",
                              {"fleet_%s" % name: cfg,
                               "fleet_prefix": {
                                   k: v for k, v in res["prefix"].items()
                                   if isinstance(v, (int, float))},
                               "fleet_disagg": {
                                   k: v for k, v in res["disagg"].items()
                                   if isinstance(v, (int, float))},
                               "fleet_remote_prefix": {
                                   k: v
                                   for k, v in res["remote_prefix"].items()
                                   if isinstance(v, (int, float))}},
                              extra=leg_obs or None)
        res.update(runlog.tail_info())
    except Exception as e:
        res["run_ledger_error"] = repr(e)[:80]
    # armed observability artifact pointers ride the END of the summary
    # (truncation-proof tail, same contract as serve_bench's trace_file/
    # telemetry_dir keys): a reader with only the last lines of a long
    # log still knows where the trace and the event journal landed
    trace_base = (os.environ.get("PADDLE_TPU_FLEET_TRACE_DIR") or "").strip()
    if trace_base:
        res["trace_dir"] = trace_base
    event_log = (os.environ.get("PADDLE_TPU_FLEET_EVENTS") or "").strip()
    if event_log:
        res["event_log"] = event_log
    print(json.dumps(res, indent=1, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
