"""Chaos drills: injected-fault recovery invariants as a CI smoke gate.

    python -m tools.chaos_drill --selftest
        JAX_PLATFORMS=cpu; drills 1-4 run in-process in a few seconds,
        the fleet drill adds real worker-process spawns. Asserts the
        recovery invariants (the ROADMAP smoke-gate entry):

        1. TRAINING — an injected preemption signal mid-run makes
           run_supervised finish the in-flight fused chunk, write a
           rotating checkpoint and stop; a fresh supervised run resumes
           from it and the combined loss trajectory is BIT-IDENTICAL to an
           uninterrupted twin (dropout included — the per-step RNG counter
           is rewound on resume). A second leg injects transient dispatch
           failures and asserts bounded retry absorbs them with the same
           bit-exact trajectory.

        2. SERVING — an injected decode failure fails the in-flight batch:
           its pages return to the pool, its requests are marked FAILED,
           and the engine keeps serving (queued requests complete). A
           second leg injects page-pool exhaustion and asserts admission
           degrades to backpressure, never a crash. Page accounting must
           balance at every terminal state.

        3. SELF-HEAL — NaN-poisoned records in the shard stream trip the
           divergence sentinel: the run rolls back to the last good
           checkpoint (model + RNG counter + reader position), quarantines
           the poisoned data window (JSONL names each record) and resumes
           PAST it — final losses are BIT-IDENTICAL (hex float32) to a
           twin trained on a stream that never contained those records.

        4. EXACTLY-ONCE — a preemption mid-run + auto-resume with a FRESH
           CheckpointableReader (zero caller-side feed_source(start)
           logic): the per-step record-id ledger of the stitched run shows
           every record consumed exactly once, matching the uninterrupted
           twin's ledger.

        5. FLEET — two real-engine worker PROCESSES behind the fleet
           router; one is SIGKILLed mid-traffic. Every request reaches
           exactly one terminal state (zero silent drops, zero duplicate
           results), the requeued seeded requests replay BIT-IDENTICAL to
           an unkilled in-process twin, and a rolling restart under
           traffic terminates nothing as 'rejected'. The leg runs with
           distributed tracing + the fleet event log armed: afterwards
           the merged clock-aligned timeline must VALIDATE (killed
           attempt 1 closed synthetically + tagged, requeued attempt 2 of
           the same trace_id finished) and the event journal must carry
           the kill/requeue/restart story on one run_id. (This leg
           dominates the gate's wall time: it spawns and warms real
           workers.)

    python -m tools.chaos_drill --parse 'site@N=kind[:times[:ms]];...'
        Validate a PADDLE_TPU_FAULT_PLAN grammar string and print the
        parsed schedule.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402


def _bits(v) -> bytes:
    return np.float32(v).tobytes()


# -- drill 1: preemption-aware training ---------------------------------------

def _build_train():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1234
    # fresh name scope per build: a resumed "process" regenerates the same
    # var names (in-process twin of a real restart)
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8])
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, size=16, act="relu")
            # dropout on purpose: resume parity must include the per-step
            # RNG stream, not just the weights
            h = fluid.layers.dropout(h, dropout_prob=0.3)
            logits = fluid.layers.fc(h, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main, startup, loss


def _feed_source(start):
    def gen():
        s = start
        while True:
            r = np.random.RandomState(1000 + s)
            yield {"x": r.randn(8, 8).astype("float32"),
                   "y": r.randint(0, 4, (8, 1)).astype("int64")}
            s += 1
    return gen()


def _supervised(ckpt_dir, plan=None, total=6):
    import paddle_tpu as fluid
    from paddle_tpu.reliability import FaultPlan, run_supervised

    main, startup, loss = _build_train()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with (plan if plan is not None else FaultPlan([])):
            return run_supervised(
                exe, main, _feed_source, total, [loss],
                checkpoint_dir=ckpt_dir, fetch_every=2,
                checkpoint_every_steps=2, backoff_s=0.0,
                exit_on_preempt=False)


def drill_training(tmp) -> None:
    from paddle_tpu.reliability import FaultPlan, faults

    full = _supervised(os.path.join(tmp, "full"))
    ref = [_bits(row[0]) for row in full.losses]
    assert full.steps_done == 6 and not full.preempted, full

    # injected preemption at the 2nd fused-chunk dispatch -> checkpoint at
    # step 4 (the in-flight chunk FINISHES first), marked stop
    ck = os.path.join(tmp, "preempt")
    plan = FaultPlan([faults.FaultSpec("executor.dispatch", "preempt", at=2)])
    first = _supervised(ck, plan)
    assert first.preempted, first
    assert first.steps_done == 4, "chunk not finished before exit: %r" % first
    assert first.checkpoints_written >= 1

    second = _supervised(ck)
    assert second.resumed and second.start_step == 4, second
    assert second.steps_done == 6 and not second.preempted, second
    stitched = [_bits(r[0]) for r in first.losses] + \
               [_bits(r[0]) for r in second.losses]
    assert stitched == ref, \
        "kill/resume loss trajectory diverged from the uninterrupted run"

    # transient dispatch failures: bounded retry absorbs them and the
    # trajectory STILL matches bit-for-bit (RNG counter rewound per retry)
    plan = FaultPlan([faults.FaultSpec("executor.dispatch", "transient",
                                       at=2, times=2)])
    retried = _supervised(os.path.join(tmp, "retry"), plan)
    assert retried.retries == 2 and retried.steps_done == 6, retried
    assert [_bits(r[0]) for r in retried.losses] == ref, \
        "retry changed the loss trajectory"
    print("chaos_drill: training drill OK "
          "(preempt@chunk2 -> resume bit-exact; 2 transient retries absorbed)")


# -- drills 3+4: sentinel self-heal + exactly-once data pipeline --------------

def _write_shards(dirname, n, poison=()):
    """Two text shards of 8-float + 1-label records (deterministic per
    record index); indices in ``poison`` get all-NaN features — parseable,
    schema-valid, numerically poisonous (that is the sentinel's job, not
    the corruption quarantine's)."""
    os.makedirs(dirname, exist_ok=True)
    paths, idx, per = [], 0, n // 2
    for si in range(2):
        p = os.path.join(dirname, "shard_%d.txt" % si)
        with open(p, "w") as f:
            for _ in range(per):
                r = np.random.RandomState(4000 + idx)
                x = np.full(8, np.nan) if idx in poison else r.randn(8)
                f.write(" ".join("%r" % float(v) for v in x)
                        + " %d\n" % r.randint(0, 4))
                idx += 1
        paths.append(p)
    return paths


def _parse_rec(line):
    t = line.split()
    return {"x": np.asarray([float(v) for v in t[:8]], np.float32),
            "y": np.asarray([int(t[8])], np.int64)}


def _reader(paths, quarantine=None):
    from paddle_tpu import data

    schema = [data.FieldSpec("x", (8,), np.float32),
              data.FieldSpec("y", (1,), np.int64)]
    return data.CheckpointableReader(paths, _parse_rec, batch_size=8,
                                     schema=schema, epochs=1,
                                     quarantine_path=quarantine)


def _supervised_reader(ckpt, reader, plan=None, total=8, sentinel=None,
                       on_chunk=None):
    """Reader-fed run_supervised over the SAME model geometry as drill 1
    (batch 8 — the compile cache collapses the rebuilds)."""
    import paddle_tpu as fluid
    from paddle_tpu.reliability import FaultPlan, run_supervised

    main, startup, loss = _build_train()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with (plan if plan is not None else FaultPlan([])):
            return run_supervised(
                exe, main, reader, total, [loss],
                checkpoint_dir=ckpt, fetch_every=2,
                checkpoint_every_steps=2, backoff_s=0.0,
                exit_on_preempt=False, sentinel=sentinel,
                on_chunk=on_chunk)


def drill_self_heal(tmp) -> None:
    import json

    from paddle_tpu.reliability import DivergenceSentinel

    # 8 steps x batch 8 = 64 committed records; poison the 16 records of
    # steps 4-5 (one fused chunk, right after the step-4 checkpoint)
    poison = set(range(32, 48))
    d_p = _write_shards(os.path.join(tmp, "heal_poison"), 80, poison)
    d_c = os.path.join(tmp, "heal_clean")
    os.makedirs(d_c, exist_ok=True)
    idx = 0
    clean = []
    for p in d_p:  # the twin's stream simply never contains the window
        q = os.path.join(d_c, os.path.basename(p))
        with open(q, "w") as f:
            for line in open(p):
                if idx not in poison:
                    f.write(line)
                idx += 1
        clean.append(q)

    qfile = os.path.join(tmp, "quarantine.jsonl")
    sent = DivergenceSentinel(nan=True, max_trips=2)
    healed = _supervised_reader(os.path.join(tmp, "ck_heal"),
                                _reader(d_p, qfile), sentinel=sent)
    twin = _supervised_reader(os.path.join(tmp, "ck_twin"), _reader(clean))
    assert len(healed.trips) == 1 and healed.trips[0].rule == "nan", healed
    assert healed.rollbacks == 1 and healed.steps_done == 8, healed
    assert healed.records_quarantined == 16, healed
    rows = [json.loads(ln) for ln in open(qfile)]
    expect = sorted("shard_%d.txt#%d" % (i // 40, i % 40)
                    for i in poison)  # 40 records per shard
    assert len(rows) == 16 and \
        sorted(r["id"] for r in rows) == expect, rows[:2]
    assert all("sentinel nan trip at step 4" in r["reason"] for r in rows)

    assert twin.steps_done == 8 and not twin.trips, twin
    hb = [_bits(r[0]) for r in healed.losses]
    tb = [_bits(r[0]) for r in twin.losses]
    assert hb == tb, \
        "healed losses not bit-identical to the never-poisoned twin"
    print("chaos_drill: self-heal drill OK (NaN window tripped the "
          "sentinel -> rollback to step 4, 16 records quarantined, "
          "healed run bit-identical to the clean twin)")


def drill_exactly_once(tmp) -> None:
    from paddle_tpu.reliability import FaultPlan, faults

    d = _write_shards(os.path.join(tmp, "once"), 80)

    def run(ckpt, plan=None):
        ledger = {}
        reader = _reader(d)  # FRESH reader: zero caller-side bookkeeping

        def on_chunk(step0, rows):
            for i, ids in enumerate(reader.last_batch_ids(len(rows))):
                ledger[step0 + i] = ids

        res = _supervised_reader(ckpt, reader, plan=plan,
                                 on_chunk=on_chunk)
        return res, ledger

    ref, ref_ledger = run(os.path.join(tmp, "ck_ref"))
    assert ref.steps_done == 8, ref

    ck = os.path.join(tmp, "ck_once")
    plan = FaultPlan([faults.FaultSpec("executor.dispatch", "preempt", at=2)])
    first, led1 = run(ck, plan)
    assert first.preempted and 0 < first.steps_done < 8, first
    second, led2 = run(ck)
    assert second.resumed and second.start_step == first.steps_done, second
    assert second.steps_done == 8 and not second.preempted, second

    stitched = dict(led1)
    stitched.update(led2)
    consumed = [rid for s in sorted(stitched) for rid in stitched[s]]
    assert sorted(stitched) == list(range(8)), sorted(stitched)
    assert len(consumed) == 64 and len(set(consumed)) == 64, \
        "records skipped or re-trained across the kill/resume boundary"
    assert stitched == ref_ledger, \
        "stitched record ledger differs from the uninterrupted twin"
    sb = [_bits(r[0]) for r in first.losses] + \
         [_bits(r[0]) for r in second.losses]
    assert sb == [_bits(r[0]) for r in ref.losses]
    print("chaos_drill: exactly-once drill OK (preempt@chunk2 + fresh-"
          "reader resume: 64 records each consumed once, ledger == twin)")


# -- drill 2: serving failure recovery ----------------------------------------

def drill_serving() -> None:
    from paddle_tpu import serving
    from paddle_tpu.models import decoder_lm
    from paddle_tpu.monitor import metrics as mx
    from paddle_tpu.reliability import FaultPlan, faults

    # one-layer toy model + a single prompt bucket: the drill exercises the
    # recovery ladder, not the model — keep every compile tiny so the gate
    # stays under its 5s budget
    cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=1, d_model=16,
                                   n_head=2, max_seq=32)
    model = decoder_lm.DecoderLM(cfg, seed=0)
    rng = np.random.RandomState(0)

    def prompts(n):
        return [(list(rng.randint(0, 64, int(rng.randint(4, 9)))),
                 int(rng.randint(2, 7))) for _ in range(n)]

    # injected decode failure (fatal after the retry budget): the in-flight
    # batch fails, the queue still drains, the engine never dies
    eng = serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=32, decode_retries=1))
    plan = FaultPlan([
        faults.FaultSpec("serving.decode", "transient", at=2, times=1),
        faults.FaultSpec("serving.decode", "fatal", at=4, times=1),
    ])
    with plan:
        reqs = [eng.submit(p, m) for p, m in prompts(5)]
        done = eng.run(max_steps=200)
    states = sorted(r.state for r in reqs)
    assert len(done) == len(reqs), "engine lost requests: %r" % states
    assert "failed" in states, "injected decode failure produced no FAILED"
    assert "finished" in states, "queue did not keep serving after failure"
    assert eng.pool.num_used == 0, "failed batch leaked pages"
    assert eng.page_accounting_ok()
    h = eng.health()
    assert h["faults_absorbed"] >= 1 and h["page_accounting_ok"], h
    for r in reqs:
        if r.state == "failed":
            assert r.error and not r.pages, r
    eng.close()

    # pool exhaustion: injected at alloc -> admission backpressures (the
    # request queues), pages retire, everything completes
    eng2 = serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=32))
    blocked0 = mx.snapshot()["serving/admission_blocked_on_pages"]["value"]
    plan = FaultPlan([faults.FaultSpec("page_pool.alloc", "exhausted",
                                       at=2, times=2)])
    with plan:
        reqs2 = [eng2.submit(p, m) for p, m in prompts(4)]
        done2 = eng2.run(max_steps=200)
    assert len(done2) == len(reqs2), "exhaustion drill did not drain"
    assert all(r.state == "finished" for r in reqs2), \
        [r.state for r in reqs2]
    assert eng2.pool.num_used == 0 and eng2.page_accounting_ok()
    blocked = mx.snapshot()["serving/admission_blocked_on_pages"]["value"]
    assert blocked > blocked0, "injected exhaustion never backpressured"
    eng2.close()

    # deadline ladder: an expired request is retired TIMEOUT, not served
    eng3 = serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=32))
    late = eng3.submit([1, 2, 3], 4, deadline_s=0.0)
    ok = eng3.submit([1, 2, 3], 4)
    eng3.run(max_steps=100)
    eng3.close()
    assert late.state == "timeout" and ok.state == "finished", \
        (late.state, ok.state)
    snap = mx.snapshot()
    for name in ("serving/faults", "serving/retries", "serving/timeouts",
                 "serving/requests_failed"):
        assert name in snap, "missing instrument %s" % name
    assert snap["serving/timeouts"]["value"] >= 1
    assert snap["serving/retries"]["value"] >= 1
    assert snap["serving/faults"]["value"] >= 1
    print("chaos_drill: serving drill OK "
          "(decode failure absorbed, exhaustion backpressured, "
          "deadline retired TIMEOUT; zero page leaks)")


def drill_fleet(tmp) -> None:
    """ISSUE 15's fleet chaos drill, on REAL engines in REAL processes:
    SIGKILL a replica mid-traffic -> exactly one terminal outcome per
    request, zero silent drops, and the requeued seeded requests replay
    bit-identical to an unkilled in-process twin; then a rolling restart
    under traffic terminates nothing as 'rejected'. The whole leg runs
    with distributed tracing + the fleet event log armed (ISSUE 16): the
    merged clock-aligned timeline must VALIDATE after the SIGKILL — the
    killed attempt 1 closed synthetically and tagged, the requeued
    attempt 2 of the SAME trace_id finished."""
    from paddle_tpu.fleet import FleetConfig, Router
    from paddle_tpu.fleet import metrics as fm
    from paddle_tpu.models.decoder_lm import DecoderConfig, DecoderLM
    from paddle_tpu.serving.engine import ServingConfig, ServingEngine

    # drill_serving's geometry: one layer, one prompt bucket, tiny
    # compiles — workers warm up fast off the shared compile cache
    mcfg = dict(vocab_size=64, n_layer=1, d_model=16, n_head=2, max_seq=32)
    scfg = dict(slots=2, page_size=8, max_seq=32)
    spec = {"engine": "real", "model": mcfg, "model_seed": 0,
            "serving": scfg, "warmup": True}
    jobs = [([1 + i, 2, 3, 4], 5) for i in range(10)]

    trace_dir = os.path.join(tmp, "fleet_trace")
    event_log = os.path.join(tmp, "fleet_events.jsonl")
    router = Router(FleetConfig(replicas=2, mode="process",
                                affinity="round_robin", engine_spec=spec,
                                max_outstanding=2, trace_dir=trace_dir,
                                event_log=event_log))
    frs = [router.submit(p, m, temperature=0.6, seed=900 + i)
           for i, (p, m) in enumerate(jobs)]
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline \
            and not router._replicas[0].inflight:
        router.pump()
        time.sleep(0.005)
    assert router._replicas[0].inflight, "no traffic reached the victim"
    req0, r0 = fm.REQUEUED.value, fm.REPLICA_RESTARTS.value
    dup0 = fm.DUPLICATE_RESULTS.value
    router._replicas[0].kill()  # real SIGKILL, KV pages and all
    assert router.wait_all(120.0), "fleet never drained after SIGKILL"
    acc = router.accounting()
    assert set(acc.values()) == {"finished"}, \
        "SIGKILL produced drops/failures: %s" % acc
    assert fm.REQUEUED.value > req0, "kill lost no in-flight work?"
    assert fm.REPLICA_RESTARTS.value > r0, "dead worker not respawned"
    assert fm.DUPLICATE_RESULTS.value == dup0, "double-terminal after kill"

    # the unkilled twin: same model seed, same request seeds, one
    # in-process engine — streams must match bit for bit
    def factory(i):
        model = DecoderLM(DecoderConfig(**mcfg), seed=0)
        return ServingEngine(model, ServingConfig(**scfg))

    twin = Router(FleetConfig(replicas=1, mode="inprocess",
                              engine_factory=factory))
    frs_t = [twin.submit(p, m, temperature=0.6, seed=900 + i)
             for i, (p, m) in enumerate(jobs)]
    assert twin.wait_all(60.0)
    assert [f.tokens for f in frs] == [f.tokens for f in frs_t], \
        "requeued replay diverged from the unkilled twin"
    twin.close()

    # rolling restart under fresh traffic: drain -> respawn each replica
    # in turn; shed work is re-routed, never terminal 'rejected'
    frs2 = [router.submit(p, m, temperature=0.6, seed=990 + i)
            for i, (p, m) in enumerate(jobs[:6])]
    rr0 = fm.ROLLING_RESTARTS.value
    router.rolling_restart(60.0)
    assert router.wait_all(120.0), "fleet never drained after restart"
    assert fm.ROLLING_RESTARTS.value > rr0
    acc = router.accounting()
    assert "rejected" not in acc.values(), \
        "rolling restart terminally rejected a request: %s" % acc
    assert all(f.state == "finished" and f.tokens for f in frs2)
    router.close()  # writes the router fragment + merge manifest

    # the merged cross-process timeline tells the same story the
    # accounting did — and validates: killed attempt 1 closed + tagged,
    # attempt 2 of the SAME trace_id finished, worker spans joined
    from tools import fleet_trace

    digest = fleet_trace.merge(trace_dir)
    digests = fleet_trace.validate(trace_dir)
    meta = digests.pop("_meta")
    assert meta["requests"] == len(jobs) + len(frs2), meta
    replayed = {t: d for t, d in digests.items() if d["killed"]}
    assert replayed, "no killed attempt in the merged trace"
    for tid, d in replayed.items():
        assert d["state"] == "finished", (tid, d)
        assert d["killed"][0] == 1 and d["attempts"][-1] >= 2, (tid, d)

    from paddle_tpu.fleet.events import read_events

    evs = read_events(event_log)
    kinds = {e["kind"] for e in evs}
    assert {"fleet_start", "kill_detected", "requeue", "restart",
            "rolling_restart", "fleet_stop"} <= kinds, kinds
    assert len({e["run_id"] for e in evs}) == 1

    print("chaos_drill: fleet drill OK (SIGKILL absorbed exactly-once, "
          "replay bit-identical to unkilled twin, rolling restart "
          "rejected nothing; merged trace validated — %d requests, "
          "killed attempt 1 -> finished attempt >=2 on %d request(s))"
          % (meta["requests"], len(replayed)))
    print("chaos_drill: fleet trace %s (merged: %s), events %s"
          % (trace_dir, digest["out"], event_log))


def selftest() -> int:
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmp:
        # the drills deliberately rebuild programs/engines ("restarted
        # process" twins) — identical HLO each time, so the persistent
        # compile cache collapses the repeat compiles and keeps the gate
        # under budget (and exercises the restart-skips-compile story)
        os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE",
                              os.path.join(tmp, "xla_cache"))
        # self-heal first: its hex-identity assert is the tightest
        # determinism gate in the suite (it caught the donated-alias
        # state-buffer corruption fixed in executor._place — keep it the
        # canary), and the later drills then reuse its compiled shapes
        drill_self_heal(tmp)
        drill_exactly_once(tmp)
        drill_training(tmp)
        drill_serving()
        drill_fleet(tmp)
    dt = time.perf_counter() - t0
    print("chaos_drill selftest: OK (%.1fs)" % dt)
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if argv and argv[0] == "--parse":
        from paddle_tpu.reliability import FaultPlan

        plan = FaultPlan.parse(argv[1] if len(argv) > 1 else "")
        for spec in plan.specs:
            print(spec)
        return 0
    if not argv or argv[0] == "--selftest":
        return selftest()
    print("unknown flag %r" % argv[0], file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
